// sparknet_tpu native runtime: record DB + threaded data pipeline.
//
// The TPU-native counterpart of the reference's native data plane:
//  - RecordDB       <- caffe's db::DB/Cursor/Transaction over LevelDB/LMDB
//                      (caffe/src/caffe/util/db.cpp, db_leveldb.cpp,
//                      db_lmdb.cpp) and the shim's create_db/write_to_db/
//                      commit_db_txn (libccaffe/ccaffe.cpp:51-81)
//  - BlockingQueue  <- caffe/src/caffe/util/blocking_queue.cpp
//  - Pipeline       <- DataReader's single reader Body thread
//                      (data_reader.cpp:80-117) + DataTransformer's
//                      scale/crop/mirror/mean (data_transformer.cpp:19-132)
//                      + BasePrefetchingDataLayer's prefetch depth
//                      (base_data_layer.cpp:70-101, PREFETCH_COUNT=3)
//
// Compute never happens here (XLA owns it); this is the host-side runtime
// that keeps the chip fed. Exposed through a minimal C ABI consumed via
// ctypes (sparknet_tpu/runtime/__init__.py).
//
// DB format "SNDB1": 8-byte magic, then records of
//   [u32 key_len][key][u32 val_len][val]  (little-endian lengths)
// Values for the pipeline are CIFAR/Datum-style: 1 label byte + C*H*W
// pixel bytes (planar, NCHW order).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

// Last-error storage is a mutex-guarded global (NOT thread_local): errors
// raised on the pipeline reader thread must be visible to the Python caller
// thread that polls sn_last_error().
std::mutex g_error_mutex;
std::string g_last_error;

void set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lock(g_error_mutex);
  g_last_error = msg;
}

std::string last_error_copy() {
  std::lock_guard<std::mutex> lock(g_error_mutex);
  return g_last_error;
}

constexpr char kMagic[8] = {'S', 'N', 'D', 'B', '1', '\0', '\0', '\0'};

// ---------------------------------------------------------------------------
// RecordDB
// ---------------------------------------------------------------------------

struct Record {
  std::string key;
  std::string value;
};

class RecordDB {
 public:
  static RecordDB* Open(const std::string& path, bool write_mode) {
    auto db = std::unique_ptr<RecordDB>(new RecordDB(path, write_mode));
    if (write_mode) {
      db->out_.open(path, std::ios::binary | std::ios::trunc);
      if (!db->out_) {
        set_error("cannot open for write: " + path);
        return nullptr;
      }
      db->out_.write(kMagic, sizeof(kMagic));
    } else {
      if (!db->LoadIndex()) return nullptr;
    }
    return db.release();
  }

  bool Put(const char* key, size_t klen, const char* val, size_t vlen) {
    std::lock_guard<std::mutex> g(mu_);
    pending_.push_back(Record{std::string(key, klen), std::string(val, vlen)});
    return true;
  }

  // Transaction commit semantics: buffered puts hit disk only here
  // (reference: CreateDB.scala commits every 1000 puts).
  bool Commit() {
    std::lock_guard<std::mutex> g(mu_);
    for (const auto& r : pending_) {
      uint32_t kl = static_cast<uint32_t>(r.key.size());
      uint32_t vl = static_cast<uint32_t>(r.value.size());
      out_.write(reinterpret_cast<const char*>(&kl), 4);
      out_.write(r.key.data(), kl);
      out_.write(reinterpret_cast<const char*>(&vl), 4);
      out_.write(r.value.data(), vl);
    }
    pending_.clear();
    out_.flush();
    return static_cast<bool>(out_);
  }

  size_t NumRecords() const { return offsets_.size(); }

  // Sequential cursor read; wraps are the caller's concern. On failure the
  // specific reason is written to *err (when given) as well as the global
  // last-error — callers on reader threads use *err to avoid racing on the
  // shared global.
  bool ReadAt(size_t idx, std::string* key, std::string* value,
              std::string* err = nullptr) {
    auto fail = [&](const std::string& msg) {
      if (err) *err = msg;
      set_error(msg);
      return false;
    };
    if (idx >= offsets_.size()) {
      return fail("record index out of range");
    }
    std::lock_guard<std::mutex> g(mu_);
    in_.seekg(offsets_[idx]);
    uint32_t kl = 0, vl = 0;
    in_.read(reinterpret_cast<char*>(&kl), 4);
    key->resize(kl);
    if (kl) in_.read(&(*key)[0], kl);
    in_.read(reinterpret_cast<char*>(&vl), 4);
    value->resize(vl);
    if (vl) in_.read(&(*value)[0], vl);
    if (!in_) {
      in_.clear();  // don't poison subsequent reads
      return fail("read failed at record " + std::to_string(idx) + " in " +
                  path_);
    }
    return true;
  }

 private:
  RecordDB(const std::string& path, bool write_mode) : path_(path) {}

  bool LoadIndex() {
    in_.open(path_, std::ios::binary);
    if (!in_) {
      set_error("cannot open for read: " + path_);
      return false;
    }
    char magic[8];
    in_.read(magic, 8);
    if (!in_ || std::memcmp(magic, kMagic, 8) != 0) {
      set_error("bad magic in " + path_);
      return false;
    }
    // bound every record against the real file size: seekg past EOF does
    // NOT set failbit, so length checks must be explicit
    in_.seekg(0, std::ios::end);
    const uint64_t fsize = static_cast<uint64_t>(in_.tellg());
    uint64_t pos = sizeof(kMagic);
    while (pos < fsize) {
      if (pos + 4 > fsize) {
        set_error("truncated record in " + path_);
        return false;
      }
      in_.seekg(pos);
      uint32_t kl = 0, vl = 0;
      in_.read(reinterpret_cast<char*>(&kl), 4);
      if (pos + 4 + kl + 4 > fsize) {
        set_error("truncated record in " + path_);
        return false;
      }
      in_.seekg(kl, std::ios::cur);
      in_.read(reinterpret_cast<char*>(&vl), 4);
      if (!in_ || pos + 4 + kl + 4 + vl > fsize) {
        set_error("truncated record in " + path_);
        return false;
      }
      offsets_.push_back(static_cast<std::streampos>(pos));
      pos += 4ull + kl + 4ull + vl;
    }
    in_.clear();
    in_.seekg(sizeof(kMagic));
    return true;
  }

  std::string path_;
  std::ofstream out_;
  std::ifstream in_;
  std::vector<std::streampos> offsets_;
  std::deque<Record> pending_;
  std::mutex mu_;
};

// ---------------------------------------------------------------------------
// BlockingQueue (util/blocking_queue.cpp)
// ---------------------------------------------------------------------------

template <typename T>
class BlockingQueue {
 public:
  explicit BlockingQueue(size_t cap) : cap_(cap) {}

  bool Push(T&& item, std::atomic<bool>& stop) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || stop.load(); });
    if (stop.load()) return false;
    q_.push_back(std::move(item));
    not_empty_.notify_one();
    return true;
  }

  bool Pop(T* item, std::atomic<bool>& stop) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || stop.load(); });
    if (q_.empty()) return false;
    *item = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void WakeAll() {
    not_full_.notify_all();
    not_empty_.notify_all();
  }

 private:
  size_t cap_;
  std::deque<T> q_;
  std::mutex mu_;
  std::condition_variable not_full_, not_empty_;
};

// ---------------------------------------------------------------------------
// Pipeline
// ---------------------------------------------------------------------------

struct Batch {
  std::vector<float> data;
  std::vector<float> labels;
};

struct PipelineConfig {
  int batch = 0, c = 0, h = 0, w = 0;
  int crop = 0;        // 0 = no crop
  bool mirror = false;
  bool train = true;   // random crop/mirror vs deterministic center crop
  float scale = 1.0f;
  std::vector<float> mean;  // empty, per-channel (C), or full image (C*H*W)
  int prefetch = 3;         // PREFETCH_COUNT
  uint32_t seed = 0;
};

class Pipeline {
 public:
  Pipeline(RecordDB* db, const PipelineConfig& cfg)
      : db_(db), cfg_(cfg), queue_(cfg.prefetch), rng_(cfg.seed) {
    out_h_ = cfg_.crop > 0 ? cfg_.crop : cfg_.h;
    out_w_ = cfg_.crop > 0 ? cfg_.crop : cfg_.w;
    thread_ = std::thread([this] { Run(); });
  }

  ~Pipeline() {
    stop_.store(true);
    queue_.WakeAll();
    if (thread_.joinable()) thread_.join();
    delete db_;
  }

  int out_h() const { return out_h_; }
  int out_w() const { return out_w_; }

  bool Next(float* data_out, float* label_out) {
    Batch b;
    if (!queue_.Pop(&b, stop_)) {
      // Surface the reader thread's sticky error if it recorded one;
      // otherwise this is an ordinary stop.
      std::string err = GetError();
      set_error(err.empty() ? "pipeline stopped" : err);
      return false;
    }
    std::memcpy(data_out, b.data.data(), b.data.size() * sizeof(float));
    std::memcpy(label_out, b.labels.data(), b.labels.size() * sizeof(float));
    return true;
  }

 private:
  void Run() {
    const size_t n = db_->NumRecords();
    const size_t record_bytes = 1 + size_t(cfg_.c) * cfg_.h * cfg_.w;
    size_t idx = 0;
    std::string key, value;
    while (!stop_.load()) {
      Batch b;
      b.data.resize(size_t(cfg_.batch) * cfg_.c * out_h_ * out_w_);
      b.labels.resize(cfg_.batch);
      for (int i = 0; i < cfg_.batch && !stop_.load(); ++i) {
        std::string read_err;
        if (!db_->ReadAt(idx, &key, &value, &read_err)) {
          SetError(read_err);
          stop_.store(true);
          break;
        }
        idx = (idx + 1) % n;  // epoch wrap, deterministic order like the
                              // reference's sequential cursor
        // Datum records carry a 1-byte label (<=255 classes) or a
        // 2-byte little-endian one (1000-class ImageNet); the width is
        // record length minus the known image size.
        if (value.size() != record_bytes && value.size() != record_bytes + 1) {
          SetError("record size mismatch: got " +
                   std::to_string(value.size()) + ", want " +
                   std::to_string(record_bytes) + " or " +
                   std::to_string(record_bytes + 1));
          stop_.store(true);
          break;
        }
        Transform(value, &b.data[size_t(i) * cfg_.c * out_h_ * out_w_],
                  &b.labels[i]);
      }
      if (stop_.load()) break;
      if (!queue_.Push(std::move(b), stop_)) break;
    }
    queue_.WakeAll();
  }

  // DataTransformer semantics: crop (random in train, center in test),
  // mirror (train only), mean subtraction, scale.
  void Transform(const std::string& value, float* out, float* label) {
    const uint8_t* bytes = reinterpret_cast<const uint8_t*>(value.data());
    const size_t label_w =
        value.size() - size_t(cfg_.c) * cfg_.h * cfg_.w;  // 1 or 2
    *label = static_cast<float>(
        label_w == 2 ? (unsigned(bytes[0]) | (unsigned(bytes[1]) << 8))
                     : bytes[0]);
    const uint8_t* img = bytes + label_w;
    int h_off = 0, w_off = 0;
    if (cfg_.crop > 0) {
      if (cfg_.train) {
        h_off = static_cast<int>(rng_() % (cfg_.h - cfg_.crop + 1));
        w_off = static_cast<int>(rng_() % (cfg_.w - cfg_.crop + 1));
      } else {
        h_off = (cfg_.h - cfg_.crop) / 2;
        w_off = (cfg_.w - cfg_.crop) / 2;
      }
    }
    bool flip = cfg_.mirror && cfg_.train && (rng_() & 1);
    const bool full_mean = cfg_.mean.size() ==
                           size_t(cfg_.c) * cfg_.h * cfg_.w;
    const bool chan_mean = cfg_.mean.size() == size_t(cfg_.c);
    for (int ch = 0; ch < cfg_.c; ++ch) {
      for (int y = 0; y < out_h_; ++y) {
        for (int x = 0; x < out_w_; ++x) {
          int sy = y + h_off;
          int sx = x + w_off;
          size_t src = (size_t(ch) * cfg_.h + sy) * cfg_.w + sx;
          float v = static_cast<float>(img[src]);
          if (full_mean) {
            v -= cfg_.mean[src];  // mean indexed by the source window,
                                  // like data_transformer.cpp
          } else if (chan_mean) {
            v -= cfg_.mean[ch];
          }
          int dx = flip ? (out_w_ - 1 - x) : x;
          out[(size_t(ch) * out_h_ + y) * out_w_ + dx] = v * cfg_.scale;
        }
      }
    }
  }

  // Per-pipeline sticky error, set on the reader thread, read by Next().
  void SetError(const std::string& msg) {
    std::lock_guard<std::mutex> lock(err_mutex_);
    if (error_.empty()) error_ = msg;
  }

  std::string GetError() {
    std::lock_guard<std::mutex> lock(err_mutex_);
    return error_;
  }

  RecordDB* db_;
  PipelineConfig cfg_;
  int out_h_, out_w_;
  BlockingQueue<Batch> queue_;
  std::mt19937 rng_;
  std::atomic<bool> stop_{false};
  std::mutex err_mutex_;
  std::string error_;
  std::thread thread_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

const char* sn_last_error() {
  // Copy into a thread_local buffer so the returned pointer stays valid for
  // the calling thread even if another thread sets a new error.
  thread_local std::string buf;
  buf = last_error_copy();
  return buf.c_str();
}

void* sndb_open(const char* path, int write_mode) {
  return RecordDB::Open(path, write_mode != 0);
}

int sndb_put(void* db, const char* key, size_t klen, const char* val,
             size_t vlen) {
  return static_cast<RecordDB*>(db)->Put(key, klen, val, vlen) ? 0 : -1;
}

int sndb_commit(void* db) {
  return static_cast<RecordDB*>(db)->Commit() ? 0 : -1;
}

long sndb_num_records(void* db) {
  return static_cast<long>(static_cast<RecordDB*>(db)->NumRecords());
}

// copies record idx's value into buf (up to buflen); returns value size or -1
long sndb_read(void* db, long idx, char* keybuf, size_t keybuflen, char* buf,
               size_t buflen) {
  std::string key, value;
  if (!static_cast<RecordDB*>(db)->ReadAt(static_cast<size_t>(idx), &key,
                                          &value)) {
    return -1;
  }
  if (keybuf && keybuflen) {
    size_t n = key.size() < keybuflen - 1 ? key.size() : keybuflen - 1;
    std::memcpy(keybuf, key.data(), n);
    keybuf[n] = '\0';
  }
  if (buf && value.size() <= buflen) {
    std::memcpy(buf, value.data(), value.size());
  }
  return static_cast<long>(value.size());
}

void sndb_close(void* db) { delete static_cast<RecordDB*>(db); }

void* snpipe_create(const char* db_path, int batch, int c, int h, int w,
                    int crop, int mirror, int train, float scale,
                    const float* mean, int mean_len, unsigned seed,
                    int prefetch) {
  RecordDB* db = RecordDB::Open(db_path, false);
  if (!db) return nullptr;
  if (db->NumRecords() == 0) {
    set_error("empty db");
    delete db;
    return nullptr;
  }
  PipelineConfig cfg;
  cfg.batch = batch;
  cfg.c = c;
  cfg.h = h;
  cfg.w = w;
  cfg.crop = crop;
  cfg.mirror = mirror != 0;
  cfg.train = train != 0;
  cfg.scale = scale;
  if (mean && mean_len > 0) cfg.mean.assign(mean, mean + mean_len);
  cfg.seed = seed;
  cfg.prefetch = prefetch > 0 ? prefetch : 3;
  if (crop > 0 && (crop > h || crop > w)) {
    set_error("crop exceeds input");
    delete db;
    return nullptr;
  }
  return new Pipeline(db, cfg);
}

int snpipe_next(void* p, float* data_out, float* label_out) {
  return static_cast<Pipeline*>(p)->Next(data_out, label_out) ? 0 : -1;
}

int snpipe_out_h(void* p) { return static_cast<Pipeline*>(p)->out_h(); }
int snpipe_out_w(void* p) { return static_cast<Pipeline*>(p)->out_w(); }

void snpipe_destroy(void* p) { delete static_cast<Pipeline*>(p); }

}  // extern "C"
